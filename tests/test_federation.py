"""Federated control plane (federation/): shard keys, front-door
routing off aggregate capacity, two-phase cross-shard gang admission
(all-or-nothing + compensating rollback + in-doubt recovery), the
federated status fold, rendezvous ownership, and the cross-shard
journal conservation audit + journal-CLI multi-shard mode.

Smoke tier: no jax — shards run the real scheduler plane over
FakeCluster slices with per-shard Journal instances in tmp dirs."""

import json
import urllib.request

import pytest

from elastic_gpu_scheduler_tpu.faultinject import FAULTS
from elastic_gpu_scheduler_tpu.federation import (
    FederationFrontDoor,
    RouterRing,
    SchedulerShard,
    shard_key,
)
from elastic_gpu_scheduler_tpu.federation.audit import (
    audit_federation,
    cross_shard_violations,
    shard_journal_dirs,
)
from elastic_gpu_scheduler_tpu.federation.ring import rendezvous_owner
from elastic_gpu_scheduler_tpu.journal import read_journal
from elastic_gpu_scheduler_tpu.journal.replay import (
    ReplayResult,
    diff_live,
    replay,
)
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster
from elastic_gpu_scheduler_tpu.k8s.objects import (
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.utils import consts


def _pod(name, core=0, gang=None, gang_size=0):
    ann = {}
    if gang:
        ann[consts.ANNOTATION_GANG_NAME] = gang
        ann[consts.ANNOTATION_GANG_SIZE] = str(gang_size)
    res = {consts.RESOURCE_TPU_CORE: core} if core else {}
    return make_pod(
        name,
        containers=[
            Container(name="main", resources=ResourceRequirements(limits=res))
        ],
        annotations=ann,
    )


def _shard(tmp_path, sid, n_nodes=4, generation="v5e"):
    cluster = FakeCluster()
    names = []
    for i in range(n_nodes):
        name = f"{sid.replace('/', '-')}-n{i}"
        cluster.add_node(make_tpu_node(
            name, chips=4, hbm_gib=64, accelerator=generation,
            slice_topology="4x4",
        ))
        names.append(name)
    # nested dirs: shard ids keep their "/"s, so the cross-shard audit
    # recovers the id from the relpath under the federation root
    sh = SchedulerShard(
        sid, FakeClientset(cluster),
        str(tmp_path / sid), node_names=names,
    )
    sh.cluster = cluster
    sh.warm()
    return sh


def _free_core(sh):
    return sh.engine.status_summary()["capacity"]["core_avail"]


@pytest.fixture
def fed(tmp_path):
    fd = FederationFrontDoor()
    a = _shard(tmp_path, "us/v5e/4x4", generation="v5e")
    b = _shard(tmp_path, "eu/v5p/4x4", generation="v5p")
    fd.add_shard(a)
    fd.add_shard(b)
    fd.refresh_summaries()
    yield fd, a, b
    FAULTS.clear()
    for sh in (a, b):
        sh.JOURNAL.close()


def test_shard_key_is_the_index_bucket_triple():
    assert shard_key("us", "v5e", "4x4") == "us/v5e/4x4"


def test_federated_summary_folds_capacity_with_staleness(fed):
    fd, a, b = fed
    s = fd.federated_summary()
    assert s["federated"] is True
    assert s["nodes"] == len(a.node_names) + len(b.node_names)
    assert (
        s["capacity"]["core_avail"] == _free_core(a) + _free_core(b)
    )
    # per-shard staleness stamps: every shard reports, fresh, alive
    assert set(s["shards"]) == {a.shard_id, b.shard_id}
    for stamp in s["shards"].values():
        assert stamp["stale_s"] >= 0.0
        assert stamp["dead"] is False
    # generation fold keeps both slices distinct
    assert "v5e" in s["generations"] and "v5p" in s["generations"]


def test_route_pod_binds_on_one_shard_and_respects_generation(fed):
    fd, a, b = fed
    p = _pod("r1", core=100)
    a.cluster.create_pod(p)
    b.cluster.create_pod(p)
    r = fd.route_pod(p, generation="v5p")
    assert r["ok"] and r["shard"] == b.shard_id
    assert _free_core(b) == 16 * 100 - 100
    assert _free_core(a) == 16 * 100


def test_cross_shard_gang_commits_all_or_nothing(fed, tmp_path):
    fd, a, b = fed
    members = []
    for j, sh in enumerate((a, b)):
        gp = _pod(f"g-m{j}", core=100, gang="g", gang_size=2)
        sh.cluster.create_pod(gp)
        members.append((sh.shard_id, sh.node_names[0], gp))
    res = fd.admit_gang("default/g", members)
    assert res["ok"]
    assert fd.decisions[res["txn"]] == "commit"
    # both shards journaled prepare→commit and replay clean
    for sh in (a, b):
        assert sh.JOURNAL.flush()
        r = replay(read_journal(sh.journal_dir))
        assert not r.violations
        assert r.fed_gangs[res["txn"]]["phases"] == ["prepare", "commit"]
        assert not diff_live(r, sh.engine.status())
    audit = audit_federation(str(tmp_path))
    assert not audit["violations"]


def test_cross_shard_gang_aborts_all_or_nothing_on_phase1_fault(fed):
    fd, a, b = fed
    base = _free_core(a) + _free_core(b)
    members = []
    for j, sh in enumerate((a, b)):
        gp = _pod(f"ab-m{j}", core=100, gang="ab", gang_size=2)
        sh.cluster.create_pod(gp)
        members.append((sh.shard_id, sh.node_names[0], gp))
    # second shard's phase-1 faults AFTER the first reserved: the first
    # must be compensated in reverse order, nothing stays charged
    FAULTS.configure(
        [{"site": "fed.prepare", "kind": "error", "nth": 2, "count": 1}],
        seed=7,
    )
    res = fd.admit_gang("default/ab", members)
    FAULTS.clear()
    assert not res["ok"]
    assert fd.decisions[res["txn"]] == "abort"
    assert _free_core(a) + _free_core(b) == base
    # the prepared shard's journal carries the compensating abort
    first = min((a, b), key=lambda s: s.shard_id)
    assert first.JOURNAL.flush()
    r = replay(read_journal(first.journal_dir))
    assert r.fed_gangs[res["txn"]]["phases"] == ["prepare", "abort"]
    assert not r.violations


def test_shard_kill_mid_phase1_recovers_by_presumed_abort(fed, tmp_path):
    fd, a, b = fed
    base = _free_core(a) + _free_core(b)
    first = min((a, b), key=lambda s: s.shard_id)
    members = []
    for j, sh in enumerate((a, b)):
        gp = _pod(f"k-m{j}", core=100, gang="k", gang_size=2)
        sh.cluster.create_pod(gp)
        members.append((sh.shard_id, sh.node_names[0], gp))
    # the first shard seals its prepare, then dies; the second shard's
    # prepare faults → abort decision, dead shard skipped by rollback
    fd.on_prepared = (
        lambda txn, sid: first.kill() if sid == first.shard_id else None
    )
    FAULTS.configure(
        [{"site": "fed.prepare", "kind": "error", "nth": 2, "count": 1}],
        seed=7,
    )
    res = fd.admit_gang("default/k", members)
    FAULTS.clear()
    fd.on_prepared = None
    assert not res["ok"]
    # revive: unknown-to-commit txn is presumed aborted from the
    # decision log, the in-doubt reservation is compensated
    rec = first.revive(fd.decisions)
    assert rec["aborted"] == [res["txn"]]
    assert _free_core(a) + _free_core(b) == base
    audit = audit_federation(str(tmp_path))
    assert not audit["violations"]


def test_shard_kill_mid_commit_resolves_forward(fed, tmp_path):
    fd, a, b = fed
    base = _free_core(a) + _free_core(b)
    first = min((a, b), key=lambda s: s.shard_id)
    members = []
    for j, sh in enumerate((a, b)):
        gp = _pod(f"c-m{j}", core=100, gang="c", gang_size=2)
        sh.cluster.create_pod(gp)
        members.append((sh.shard_id, sh.node_names[0], gp))
    FAULTS.configure(
        [{"site": "fed.commit", "kind": "error", "nth": 1, "count": 1}],
        seed=7,
    )
    res = fd.admit_gang("default/c", members)
    FAULTS.clear()
    assert res["ok"] and res["unresolved"] == [first.shard_id]
    first.kill()
    rec = first.revive(fd.decisions)
    assert rec["committed"] == [res["txn"]]
    # members stay charged after forward-commit recovery
    assert _free_core(a) + _free_core(b) == base - 200
    for sh in (a, b):
        assert sh.JOURNAL.flush()
    audit = audit_federation(str(tmp_path))
    assert not audit["violations"]


def test_frontdoor_http_serves_federated_summary_and_debug(fed):
    fd, a, b = fed
    port = fd.start()
    try:
        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as r:
                return json.loads(r.read())

        assert get("/healthz")["ok"] is True
        s = get("/scheduler/status?summary=1")
        assert s["federated"] is True
        assert s["nodes"] == len(a.node_names) + len(b.node_names)
        dbg = get("/debug/federation")
        assert set(dbg["shards"]) == {a.shard_id, b.shard_id}
    finally:
        fd.stop()


def test_rendezvous_owner_resteers_only_lost_keys():
    keys = [f"key-{i}".encode() for i in range(200)]
    three = {k: rendezvous_owner(["a", "b", "c"], k) for k in keys}
    two = {k: rendezvous_owner(["a", "b"], k) for k in keys}
    moved = [k for k in keys if three[k] != two[k]]
    # exactly the keys c owned move; a/b-owned keys stay put
    assert moved == [k for k in keys if three[k] == "c"]
    assert 0 < len(moved) < len(keys)


def test_router_ring_steers_continuations_to_one_owner():
    ring = RouterRing(page_size=4)
    ring.add_router("r0", object())
    ring.add_router("r1", object())
    prefix = [1, 2, 3, 4]
    keys = {
        ring.steer_key({"prompt": prefix + extra}).hex()
        for extra in ([], [5], [5, 6], [7, 8, 9])
    }
    # every continuation shares the chain root → one steering key
    assert len(keys) == 1
    # different adapters place the same tokens in different keyspaces
    assert ring.steer_key({"prompt": prefix}) != ring.steer_key(
        {"prompt": prefix, "adapter": "lora-x"}
    )


def test_cross_shard_audit_flags_disagreement_and_unresolved():
    def _res(fed_gangs):
        r = ReplayResult()
        r.fed_gangs = fed_gangs
        return r

    # terminal disagreement: one commits, one aborts
    split = cross_shard_violations({
        "a": _res({"t1": {"phases": ["prepare", "commit"],
                          "shards": ["a", "b"]}}),
        "b": _res({"t1": {"phases": ["prepare", "abort"],
                          "shards": ["a", "b"]}}),
    })
    assert any("disagree" in v for v in split)
    # unresolved prepare
    stuck = cross_shard_violations({
        "a": _res({"t2": {"phases": ["prepare"], "shards": ["a"]}}),
    })
    assert any("unresolved" in v for v in stuck)
    # committed with a silent declared participant
    silent = cross_shard_violations({
        "a": _res({"t3": {"phases": ["prepare", "commit"],
                          "shards": ["a", "b"]}}),
        "b": _res({}),
    })
    assert any("no record" in v for v in silent)
    # aborted with a silent participant is the EXPECTED shape of a
    # shard whose phase 1 faulted before journaling — not a violation
    quiet_abort = cross_shard_violations({
        "a": _res({"t4": {"phases": ["prepare", "abort"],
                          "shards": ["a", "b"]}}),
        "b": _res({}),
    })
    assert quiet_abort == []


def test_journal_cli_replays_directory_of_shard_journals(fed, tmp_path):
    from elastic_gpu_scheduler_tpu.journal.__main__ import main as jmain

    fd, a, b = fed
    members = []
    for j, sh in enumerate((a, b)):
        gp = _pod(f"cli-m{j}", core=100, gang="cli", gang_size=2)
        sh.cluster.create_pod(gp)
        members.append((sh.shard_id, sh.node_names[0], gp))
    assert fd.admit_gang("default/cli", members)["ok"]
    for sh in (a, b):
        assert sh.JOURNAL.flush()
    # root holds two shard journal dirs → federated mode, clean exit
    dirs = shard_journal_dirs(str(tmp_path))
    assert len(dirs) == 2
    assert jmain(["replay", "--dir", str(tmp_path)]) == 0
    assert jmain(["replay", "--dir", str(tmp_path), "--json"]) == 0
    # a single shard dir still takes the single-stream path
    assert jmain(["replay", "--dir", a.journal_dir]) == 0
    # --status is single-stream only in federated mode
    assert jmain(
        ["replay", "--dir", str(tmp_path), "--status", "x.json"]
    ) == 2


def test_shard_key_for_entry_matches_index_bucket(fed):
    from elastic_gpu_scheduler_tpu.core.index import topo_class
    from elastic_gpu_scheduler_tpu.federation import shard_key_for_entry

    fd, a, b = fed
    idx = a.engine.index
    entry = next(iter(idx.entries.values()))
    key = shard_key_for_entry("us", entry)
    assert key == f"us/{entry.generation}/{topo_class(entry.topo_key)}"
    assert key.startswith("us/v5e/4x4")


def test_merged_sources_folds_router_shard_replica_lists():
    from elastic_gpu_scheduler_tpu.slo.assembly import merged_sources

    r0 = lambda: [("a", ("127.0.0.1", 1)), ("b", ("127.0.0.1", 2))]
    r1 = lambda: [("b", ("127.0.0.1", 2)), ("c", ("127.0.0.1", 3))]
    fold = merged_sources(r0, r1)
    assert fold() == [
        ("a", ("127.0.0.1", 1)),
        ("b", ("127.0.0.1", 2)),
        ("c", ("127.0.0.1", 3)),
    ]
