"""Data pipeline tests: memmap round-trip, process-sharded batching, and
actual learnability of the synthetic motif language."""

import os
import tempfile

import jax
import numpy as np
import pytest

from elastic_gpu_scheduler_tpu.models.data import (
    MemmapTokenDataset,
    SyntheticTokenDataset,
    batches,
    write_token_file,
)
from elastic_gpu_scheduler_tpu.models.train import (
    init_sharded_state,
    make_jitted_train_step,
    make_optimizer,
)
from elastic_gpu_scheduler_tpu.models.transformer import TransformerConfig


def test_memmap_roundtrip_and_window():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "toks.bin")
        toks = np.arange(1000) % 500
        write_token_file(path, toks)
        ds = MemmapTokenDataset(path)
        assert len(ds) == 1000
        w = ds.window(10, 16)
        np.testing.assert_array_equal(w, toks[10:26])
        assert w.dtype == np.int32
        # start is reduced modulo the valid range; never runs off the end
        w2 = ds.window(999, 16)
        assert len(w2) == 16
        # the LAST valid start (len - length) is reachable (ADVICE r1:
        # start % valid excluded it); the final token must be coverable
        w3 = ds.window(1000 - 16, 16)
        np.testing.assert_array_equal(w3, toks[-16:])
        # an exact-length file has exactly one window
        exact = os.path.join(d, "exact.bin")
        write_token_file(exact, np.arange(16))
        np.testing.assert_array_equal(
            MemmapTokenDataset(exact).window(7, 16), np.arange(16)
        )
        # a file shorter than the window is an error, not a short batch
        short = os.path.join(d, "short.bin")
        write_token_file(short, np.arange(10))
        with pytest.raises(ValueError, match="< window"):
            MemmapTokenDataset(short).window(0, 16)


def test_batches_process_sharding_is_partition():
    """Two processes' local batches concatenate to the single-process batch."""
    ds = SyntheticTokenDataset(vocab_size=64, seed=1)
    full = next(batches(ds, batch_size=8, seq_len=12, seed=5))
    p0 = next(batches(ds, 8, 12, seed=5, process_index=0, process_count=2))
    p1 = next(batches(ds, 8, 12, seed=5, process_index=1, process_count=2))
    np.testing.assert_array_equal(np.concatenate([p0, p1]), full)
    assert full.shape == (8, 13)
    with pytest.raises(ValueError):
        next(batches(ds, 9, 12, process_count=2))


def test_synthetic_language_is_learnable():
    """Training on motifs beats training on uniform noise by a clear margin."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    opt = make_optimizer(lr=3e-3, grad_clip=1.0)
    params, opt_state = init_sharded_state(jax.random.key(0), cfg, opt)
    step = make_jitted_train_step(cfg, opt)
    ds = SyntheticTokenDataset(vocab_size=64, seed=2, noise=0.05)
    it = batches(ds, batch_size=16, seq_len=32, seed=3)
    loss = None
    for i in range(60):
        tokens = jax.numpy.asarray(next(it))
        params, opt_state, loss = step(params, opt_state, tokens)
    # uniform-noise entropy is ln(64) ≈ 4.16; motifs must be far below
    assert float(loss) < 2.5, float(loss)


def test_optimizer_schedule_and_clip():
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype="float32",
    )
    opt = make_optimizer(lr=1e-2, warmup_steps=5, total_steps=20, grad_clip=0.5)
    params, opt_state = init_sharded_state(jax.random.key(0), cfg, opt)
    step = make_jitted_train_step(cfg, opt)
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, 32)
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
