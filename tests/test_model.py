"""Workload-plane tests: model math, flash/ring attention numerics, and the
sharded train step on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elastic_gpu_scheduler_tpu.models.train import (
    cross_entropy_loss,
    init_sharded_state,
    loss_fn,
    make_jitted_train_step,
    make_optimizer,
)
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    param_count,
)
from elastic_gpu_scheduler_tpu.ops.attention import (
    _flash_forward_pallas,
    flash_attention,
    mha_reference,
)
from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh
from elastic_gpu_scheduler_tpu.parallel.ring import ring_attention_sharded

CFG = TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128, dtype="float32"
)


def test_forward_shapes_and_determinism():
    params = init_params(jax.random.key(0), CFG)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, CFG.vocab_size)
    logits = forward(params, tokens, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert jnp.all(jnp.isfinite(logits))
    logits2 = forward(params, tokens, CFG)
    np.testing.assert_array_equal(logits, logits2)
    assert param_count(params) > 0


def test_causality():
    """Changing a future token must not change past logits."""
    params = init_params(jax.random.key(0), CFG)
    tokens = jax.random.randint(jax.random.key(1), (1, 16), 0, CFG.vocab_size)
    logits_a = forward(params, tokens, CFG)
    tokens_b = tokens.at[0, 10].set((tokens[0, 10] + 1) % CFG.vocab_size)
    logits_b = forward(params, tokens_b, CFG)
    np.testing.assert_allclose(
        logits_a[0, :10], logits_b[0, :10], rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(logits_a[0, 10:], logits_b[0, 10:])


def test_flash_matches_reference_pallas_interpret():
    """The Pallas kernel (interpret mode on CPU) matches the reference math."""
    key = jax.random.key(0)
    B, H, S, D = 2, 2, 256, 64
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    ref, _ = mha_reference(q, k, v, causal=True, sm_scale=D**-0.5)
    out = _flash_forward_pallas(
        q, k, v, causal=True, sm_scale=D**-0.5, block_q=128, block_k=128,
        interpret=True,
    )
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("resident", [True, False])
def test_flash_rectangular_causal_matches_reference(resident):
    """sq != sk causal: kernel q_ids must carry the (sk - sq) offset so the
    queries align to the LAST sq key positions (ADVICE r1 medium).
    Covers both the VMEM-resident and the streamed kernel variants."""
    key = jax.random.key(11)
    B, H, D = 1, 2, 32
    for sq, sk, window in ((128, 256, 0), (128, 384, 0), (128, 256, 100)):
        kq, kk_, kv = jax.random.split(jax.random.key(sq + sk + window), 3)
        q = jax.random.normal(kq, (B, H, sq, D), jnp.float32)
        k = jax.random.normal(kk_, (B, H, sk, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, sk, D), jnp.float32)
        ref, _ = mha_reference(q, k, v, causal=True, sm_scale=D**-0.5,
                               window=window)
        out = _flash_forward_pallas(
            q, k, v, causal=True, sm_scale=D**-0.5, block_q=64, block_k=128,
            interpret=True, window=window, resident=resident,
        )
        np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-2,
                                   err_msg=f"sq={sq} sk={sk} window={window}")


def test_flash_attention_grads_match_reference():
    key = jax.random.key(7)
    B, H, S, D = 1, 2, 32, 16
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def f_ref(q, k, v):
        out, _ = mha_reference(q, k, v)
        return jnp.sum(out**2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-3)


def test_ring_attention_matches_full():
    """Ring attention over the 8-device seq axis == single-device attention."""
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    mesh = make_mesh(MeshSpec(seq=8, fsdp=1), jax.devices()[:8])
    key = jax.random.key(3)
    B, H, S, D = 2, 1, 64, 16  # S=64 → 8 per shard
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    ref, _ = mha_reference(q, k, v, causal=True)
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_ring_attention_non_causal():
    mesh = make_mesh(MeshSpec(seq=8, fsdp=1), jax.devices()[:8])
    key = jax.random.key(4)
    B, H, S, D = 1, 2, 32, 8
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    ref, _ = mha_reference(q, k, v, causal=False)
    out = ring_attention_sharded(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_train_step_decreases_loss_single_device():
    cfg = CFG
    opt = make_optimizer(lr=1e-2)
    params, opt_state = init_sharded_state(jax.random.key(0), cfg, opt)
    step = make_jitted_train_step(cfg, opt)
    tokens = jax.random.randint(jax.random.key(1), (4, 33), 0, cfg.vocab_size)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_sharded_train_step_8_devices():
    """Full SPMD train step over a data×fsdp×tensor×seq mesh (2x1x2x2)."""
    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        dtype="float32", use_ring_attention=True, remat=True,
    )
    mesh = make_mesh(MeshSpec(data=2, fsdp=1, tensor=2, seq=2))
    opt = make_optimizer(lr=1e-2)
    params, opt_state = init_sharded_state(jax.random.key(0), cfg, opt, mesh)
    step = make_jitted_train_step(cfg, opt, mesh)
    tokens = jax.random.randint(jax.random.key(1), (4, 33), 0, cfg.vocab_size)
    params, opt_state, loss1 = step(params, opt_state, tokens)
    params, opt_state, loss2 = step(params, opt_state, tokens)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)


def test_sharded_matches_unsharded():
    """The 8-device sharded forward computes the same logits as 1 device."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    ref = forward(params, tokens, cfg)

    from elastic_gpu_scheduler_tpu.parallel import sharding as shardlib

    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2, seq=1))
    params_s = shardlib.shard_params(params, mesh)
    out = jax.jit(lambda p, t: forward(p, t, cfg, mesh=None))(params_s, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_ring_attention_long_context_causality():
    """Long context beyond toy size: 8192 tokens over the 8-device seq axis
    (1024/shard).  The O(S^2) full reference is too big to compare, so assert
    the defining properties instead: finite outputs, and perturbing the LAST
    sequence shard leaves the FIRST shard's outputs bit-identical (causality
    across ring hops)."""
    mesh = make_mesh(MeshSpec(seq=8, fsdp=1))
    key = jax.random.key(11)
    B, H, S, D = 1, 2, 8192, 32
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    f = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=True))
    out_a = f(q, k, v)
    assert bool(jnp.all(jnp.isfinite(out_a)))
    # perturb the final shard's keys/values/queries
    k2 = k.at[:, :, -1024:, :].add(1.0)
    v2 = v.at[:, :, -1024:, :].add(1.0)
    out_b = f(q, k2, v2)
    np.testing.assert_array_equal(
        np.asarray(out_a[:, :, :7168]), np.asarray(out_b[:, :, :7168])
    )
    assert not np.allclose(
        np.asarray(out_a[:, :, -1024:]), np.asarray(out_b[:, :, -1024:])
    )


def test_flash_block_stats_matches_ring_reference():
    """The Pallas stats kernel (interpret mode) equals the ring-attention
    reference block math at several global offsets."""
    from elastic_gpu_scheduler_tpu.ops.attention import flash_block_stats
    from elastic_gpu_scheduler_tpu.parallel.ring import _block_attend

    B, H, S, D = 2, 4, 256, 64
    key = jax.random.key(0)
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    for qo, ko in [(0, 0), (256, 0), (0, 256), (512, 256)]:
        ref_pv, ref_m, ref_l = _block_attend(q, k, v, qo, ko, True, D**-0.5)
        pv, m, l = flash_block_stats(q, k, v, qo, ko, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(m), np.asarray(ref_m), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(l), np.asarray(ref_l), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(pv), np.asarray(ref_pv), rtol=1e-2, atol=1e-2)


def test_flash_kernel_sliding_window_interpret():
    from elastic_gpu_scheduler_tpu.ops.attention import _flash_forward_pallas

    B, H, S, D = 1, 2, 384, 64
    key = jax.random.key(5)
    q, k, v = (
        jax.random.normal(kk, (B, H, S, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    for w in (64, 200):
        ref, _ = mha_reference(q, k, v, causal=True, window=w)
        out = _flash_forward_pallas(
            q, k, v, causal=True, sm_scale=D**-0.5, block_q=128, block_k=128,
            interpret=True, window=w,
        )
        np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-2)


def test_bf16_at_rest_params_and_master_weights():
    """bf16 params at rest: forward matches fp32-at-rest exactly (compute
    casts to bf16 either way), training runs on an fp32 master copy, and
    loss still decreases (VERDICT r1 #3 recipe)."""
    from elastic_gpu_scheduler_tpu.models.train import MasterState
    from elastic_gpu_scheduler_tpu.models.transformer import cast_params_to_rest

    cfg16 = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="bfloat16",
    )
    params16 = init_params(jax.random.key(0), cfg16)
    # big matmul weights live in bf16; norm scales stay fp32
    assert params16["layers"]["wq"].dtype == jnp.bfloat16
    assert params16["embed"].dtype == jnp.bfloat16
    assert params16["layers"]["attn_norm"].dtype == jnp.float32
    assert params16["final_norm"].dtype == jnp.float32

    # same init in fp32-at-rest form → identical logits (compute casts)
    cfg32rest = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="bfloat16", params_dtype="float32",
    )
    params_ref = init_params(jax.random.key(0), cfg32rest)
    assert params_ref["layers"]["wq"].dtype == jnp.float32
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    np.testing.assert_array_equal(
        forward(params16, tokens, cfg16), forward(params_ref, tokens, cfg32rest)
    )

    # training: fp32 master in the optimizer state, params stay bf16
    opt = make_optimizer(lr=1e-2)
    params, opt_state = init_sharded_state(jax.random.key(0), cfg16, opt)
    assert isinstance(opt_state, MasterState)
    assert opt_state.master["layers"]["wq"].dtype == jnp.float32
    step = make_jitted_train_step(cfg16, opt)
    toks = jax.random.randint(jax.random.key(2), (4, 17), 0, 128)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, toks)
        losses.append(float(loss))
    assert params["layers"]["wq"].dtype == jnp.bfloat16
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_pallas_backward_matches_reference_s4096():
    """Pallas backward kernels (interpret mode): grads match the reference
    at S=4096 with NO (S,S) intermediate in the compiled backward
    (VERDICT r1 #8)."""
    from elastic_gpu_scheduler_tpu.ops.attention import (
        _flash_backward_pallas,
        _flash_forward_pallas,
    )

    B, H, S, D = 1, 1, 4096, 32
    kq, kk, kv, kd = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(kq, (B, H, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, H, S, D), jnp.float32)
    v = jax.random.normal(kv, (B, H, S, D), jnp.float32)
    do = jax.random.normal(kd, (B, H, S, D), jnp.float32)
    scale = D**-0.5

    out, lse = _flash_forward_pallas(
        q, k, v, causal=True, sm_scale=scale, block_q=512, block_k=512,
        interpret=True, return_lse=True,
    )
    ref_out, ref_lse = mha_reference(q, k, v, causal=True, sm_scale=scale)
    np.testing.assert_allclose(out, ref_out, rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(lse, ref_lse, rtol=1e-4, atol=1e-4)

    def bwd(q, k, v, out, lse, do):
        # force the STREAMED kernels — the long-context path this test proves
        return _flash_backward_pallas(
            q, k, v, out, lse, do, True, scale, interpret=True, resident=False
        )

    jitted_bwd = jax.jit(bwd)
    dq, dk, dv = jitted_bwd(q, k, v, out, lse, do)

    def ref_loss(q, k, v):
        o, _ = mha_reference(q, k, v, causal=True, sm_scale=scale)
        return jnp.sum(o * do)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(dq, rq, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(dk, rk, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(dv, rv, rtol=2e-2, atol=2e-2)

    # the compiled backward must not allocate any (S, S)-shaped buffer —
    # that is the entire point of the blockwise kernels
    hlo = jitted_bwd.lower(q, k, v, out, lse, do).compile().as_text()
    assert f"{S},{S}" not in hlo, "backward materializes an (S,S) buffer"


@pytest.mark.parametrize("resident", [True, False])
def test_pallas_backward_window_and_rectangular(resident):
    """Backward kernels (both variants) honor sliding-window and sq != sk
    causal masks."""
    from elastic_gpu_scheduler_tpu.ops.attention import (
        _flash_backward_pallas,
        _flash_forward_pallas,
    )

    B, H, D = 1, 2, 16
    for sq, sk, window in ((256, 256, 100), (128, 256, 0), (128, 256, 60)):
        keys = jax.random.split(jax.random.key(sq + sk + window), 4)
        q = jax.random.normal(keys[0], (B, H, sq, D), jnp.float32)
        k = jax.random.normal(keys[1], (B, H, sk, D), jnp.float32)
        v = jax.random.normal(keys[2], (B, H, sk, D), jnp.float32)
        do = jax.random.normal(keys[3], (B, H, sq, D), jnp.float32)
        scale = D**-0.5
        out, lse = _flash_forward_pallas(
            q, k, v, causal=True, sm_scale=scale, block_q=64, block_k=64,
            interpret=True, window=window, return_lse=True,
        )
        dq, dk, dv = _flash_backward_pallas(
            q, k, v, out, lse, do, True, scale, block_q=64, block_k=64,
            interpret=True, window=window, resident=resident,
        )

        def ref_loss(q, k, v):
            o, _ = mha_reference(q, k, v, causal=True, sm_scale=scale,
                                 window=window)
            return jnp.sum(o * do)

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        err = f"sq={sq} sk={sk} w={window}"
        np.testing.assert_allclose(dq, rq, rtol=2e-2, atol=2e-2, err_msg=err)
        np.testing.assert_allclose(dk, rk, rtol=2e-2, atol=2e-2, err_msg=err)
        np.testing.assert_allclose(dv, rv, rtol=2e-2, atol=2e-2, err_msg=err)


def test_grad_accumulation_matches_full_batch():
    """grad_accum=4 microbatches == one full-batch step.  The update
    comparison uses SGD (Adam's first-step update is ~sign(g), which
    amplifies ulp-level reduction-order differences); Adam + bf16
    MasterState get a loss-trajectory smoke check."""
    import optax

    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    sgd = optax.sgd(1e-2)
    tokens = jax.random.randint(jax.random.key(1), (8, 17), 0, 128)
    p_full, s_full = init_sharded_state(jax.random.key(0), cfg, sgd)
    p_acc, s_acc = init_sharded_state(jax.random.key(0), cfg, sgd)
    step_full = make_jitted_train_step(cfg, sgd)
    step_acc = make_jitted_train_step(cfg, sgd, grad_accum=4)
    p_full, s_full, loss_full = step_full(p_full, s_full, tokens)
    p_acc, s_acc, loss_acc = step_acc(p_acc, s_acc, tokens)
    np.testing.assert_allclose(float(loss_full), float(loss_acc), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_acc)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )

    # Adam + bf16 MasterState: accumulated steps still train
    cfg16 = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="bfloat16",
    )
    opt = make_optimizer(lr=1e-2)
    p, s = init_sharded_state(jax.random.key(0), cfg16, opt)
    step = make_jitted_train_step(cfg16, opt, grad_accum=4)
    losses = []
    for _ in range(6):
        p, s, loss = step(p, s, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    # evaluate(): finite loss/perplexity over a couple of batches
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)
    batches = [
        jax.random.randint(jax.random.key(i), (4, 17), 0, 128)
        for i in range(3)
    ]
    from elastic_gpu_scheduler_tpu.models.train import evaluate

    m = evaluate(params, cfg, batches)
    assert m["batches"] == 3 and np.isfinite(m["loss"])
    assert m["perplexity"] > 1.0
