"""Draft-model speculative decoding in the paged engine.

Correctness bar is the same as prompt-lookup speculation
(tests/test_spec_engine.py): greedy engine outputs with a draft model are
TOKEN-IDENTICAL to the non-speculative engine — the draft changes only the
acceptance rate, never the tokens.  The acceptance test uses the target
model itself as the draft: every greedy draft then matches the target's
choice, so each verify pass must accept the full window.
"""

import jax
import numpy as np

from elastic_gpu_scheduler_tpu.models.serving import InferenceEngine, Request
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = TransformerConfig(
    vocab_size=97, d_model=48, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=96, dtype="float32",
)
PARAMS = init_params(jax.random.key(0), CFG)
DRAFT_CFG = TransformerConfig(
    vocab_size=97, d_model=32, n_layers=1, n_heads=2, d_ff=64,
    dtype="float32",
)
DRAFT_PARAMS = init_params(jax.random.key(7), DRAFT_CFG)
PROMPTS = [[5, 17, 3], [60, 2, 9, 9, 9, 9], list(range(1, 20)), [42, 5]]


def run(draft=None, spec_k=0, temps=None, new=10):
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=4, max_len=96, page_size=8,
        spec_k=spec_k, draft=draft,
    )
    reqs = []
    for n, p in enumerate(PROMPTS):
        t = (temps or [0.0] * len(PROMPTS))[n]
        reqs.append(eng.submit(
            Request(prompt=p, max_new_tokens=new, temperature=t)
        ))
    eng.run_until_idle()
    for r in reqs:
        assert r.done.is_set() and not r.error, r.error
    return [r.output for r in reqs], eng


def test_draft_model_outputs_token_identical():
    """An UNRELATED random draft (mostly-wrong drafts) must not change a
    single output token vs the plain engine."""
    base, _ = run()
    got, eng = run(draft=(DRAFT_PARAMS, DRAFT_CFG), spec_k=3)
    assert got == base
    assert eng.spec_passes > 0


def test_self_draft_accepts_full_window():
    """Target-as-draft: every greedy draft token matches the target's own
    choice, so acceptance per pass approaches the full window."""
    _, eng = run(draft=(PARAMS, CFG), spec_k=4, new=16)
    assert eng.spec_passes > 0
    # 4 slots × spec_k accepted per steady-state pass; prompt-feeding and
    # tail passes dilute, so demand a conservative 1.5/slot-pass average
    assert eng.spec_accepted >= eng.spec_passes * 1.5, (
        eng.spec_accepted, eng.spec_passes,
    )
    base, _ = run(new=16)
    got, _ = run(draft=(PARAMS, CFG), spec_k=4, new=16)
    assert got == base


def test_self_draft_acceptance_survives_prompt_boundary():
    """Regression: the first generation pass after a long prompt must
    still roll drafts from the last REAL token's logits (not a pad's), so
    a perfect draft keeps near-full acceptance from the very first
    generating pass."""
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=1, max_len=96, page_size=8,
        spec_k=4, draft=(PARAMS, CFG),
    )
    prompt = [(3 * i) % 97 for i in range(20)]  # longer than the window
    r = eng.submit(Request(prompt=prompt, max_new_tokens=20))
    eng.run_until_idle()
    assert r.done.is_set() and not r.error, r.error
    # perfect drafts: every generating pass accepts spec_k drafts + bonus;
    # ~20 tokens in ~4 passes → accepted ≈ 16.  Garbage boundary drafts
    # would halve this.
    assert eng.spec_accepted >= 12, (eng.spec_accepted, eng.spec_passes)

    plain = InferenceEngine(PARAMS, CFG, max_batch=1, max_len=96, page_size=8)
    r2 = plain.submit(Request(prompt=prompt, max_new_tokens=20))
    plain.run_until_idle()
    assert r.output == r2.output


def test_draft_with_mixed_sampled_batch():
    """Sampled slots coexist with draft-speculated greedy slots; greedy
    rows stay identical to the plain engine's."""
    temps = [0.0, 0.9, 0.0, 0.0]
    base, _ = run(temps=temps)
    got, _ = run(draft=(DRAFT_PARAMS, DRAFT_CFG), spec_k=3, temps=temps)
    for n, t in enumerate(temps):
        if t == 0.0:
            assert got[n] == base[n], f"greedy row {n} diverged"


def test_draft_long_prompt_chunked_ingest():
    """A prompt longer than the ingest chunk still catches up correctly
    (exercises the chunked pre-ingest loop)."""
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=2, max_len=160, page_size=8,
        spec_k=3, draft=(DRAFT_PARAMS, DRAFT_CFG),
    )
    eng._draft_chunk = 16  # force several chunk iterations
    long_prompt = [(7 * i) % 97 for i in range(90)]
    r = eng.submit(Request(prompt=long_prompt, max_new_tokens=8))
    eng.run_until_idle()
    assert r.done.is_set() and not r.error, r.error

    plain = InferenceEngine(PARAMS, CFG, max_batch=2, max_len=160, page_size=8)
    r2 = plain.submit(Request(prompt=long_prompt, max_new_tokens=8))
    plain.run_until_idle()
    assert r.output == r2.output


def test_draft_rejects_bad_configs():
    import pytest

    bad_vocab = TransformerConfig(
        vocab_size=50, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        dtype="float32",
    )
    with pytest.raises(ValueError, match="vocab"):
        InferenceEngine(PARAMS, CFG, spec_k=3,
                        draft=(init_params(jax.random.key(1), bad_vocab),
                               bad_vocab))
    with pytest.raises(ValueError, match="spec_k"):
        InferenceEngine(PARAMS, CFG, draft=(DRAFT_PARAMS, DRAFT_CFG))


def test_draft_composes_with_tp_mesh():
    """draft + mesh: the draft replicates across the mesh while the target
    shards; outputs stay identical to the single-device plain engine."""
    from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

    base, _ = run()
    mesh = make_mesh(MeshSpec(tensor=2), jax.devices()[:2])
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=4, max_len=96, page_size=8,
        spec_k=3, draft=(DRAFT_PARAMS, DRAFT_CFG), mesh=mesh,
    )
    reqs = [eng.submit(Request(prompt=p, max_new_tokens=10)) for p in PROMPTS]
    eng.run_until_idle()
    got = []
    for r in reqs:
        assert r.done.is_set() and not r.error, r.error
        got.append(r.output)
    assert got == base
