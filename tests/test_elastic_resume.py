"""Elastic resume: a training job checkpointed on one mesh resumes on a
DIFFERENT mesh shape (more chips, fewer chips, or a single device), with
identical training trajectory.

This is the workload-plane meaning of "elastic": the scheduler can place a
rescheduled job on whatever slice is free, and the checkpoint reshapes to
the new device topology (orbax restores to the templates' shardings).
"""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from elastic_gpu_scheduler_tpu.models.checkpoint import CheckpointManager
from elastic_gpu_scheduler_tpu.models.train import (
    init_sharded_state,
    make_jitted_train_step,
    make_optimizer,
)
from elastic_gpu_scheduler_tpu.models.transformer import TransformerConfig
from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

CFG = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64, dtype="float32"
)


def _train(params, opt_state, step_fn, tokens, n):
    losses = []
    for _ in range(n):
        params, opt_state, loss = step_fn(params, opt_state, tokens)
        losses.append(float(loss))
    return params, opt_state, losses


def test_elastic_resume_across_mesh_shapes():
    assert jax.device_count() >= 8
    opt = make_optimizer(lr=1e-2)
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, CFG.vocab_size)

    # original job: 4 chips, data x tensor
    mesh_a = make_mesh(MeshSpec(data=2, tensor=2), jax.devices()[:4])
    params, opt_state = init_sharded_state(jax.random.key(0), CFG, opt, mesh_a)
    step_a = make_jitted_train_step(CFG, opt, mesh_a)
    params, opt_state, _ = _train(params, opt_state, step_a, tokens, 2)

    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        mgr.save(params, opt_state, step=2)

        # the reference trajectory: continue on the ORIGINAL mesh
        _, _, ref_losses = _train(params, opt_state, step_a, tokens, 2)

        # resume on three different topologies the scheduler might hand us
        resumes = {
            "grow-to-8": make_mesh(
                MeshSpec(data=2, fsdp=2, tensor=2), jax.devices()[:8]
            ),
            "shrink-to-2": make_mesh(MeshSpec(data=2), jax.devices()[:2]),
            "single-chip": None,
        }
        for name, mesh_b in resumes.items():
            params_t, opt_t = init_sharded_state(
                jax.random.key(9), CFG, opt, mesh_b
            )  # template: structure + target shardings (values discarded)
            restored = mgr.restore(params_t, opt_t)
            assert restored is not None, name
            r_params, r_opt, step = restored
            assert step == 2
            step_b = make_jitted_train_step(CFG, opt, mesh_b)
            _, _, losses = _train(r_params, r_opt, step_b, tokens, 2)
            np.testing.assert_allclose(
                losses, ref_losses, rtol=2e-4, atol=2e-4,
                err_msg=f"trajectory diverged after elastic resume: {name}",
            )
        mgr.close()


def test_elastic_resume_bf16_master_state():
    """bf16-at-rest jobs (MasterState optimizer wrapper) also reshard."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="bfloat16",
    )
    from elastic_gpu_scheduler_tpu.models.train import MasterState

    opt = make_optimizer(lr=1e-2)
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, cfg.vocab_size)
    mesh_a = make_mesh(MeshSpec(data=2, tensor=2), jax.devices()[:4])
    params, opt_state = init_sharded_state(jax.random.key(0), cfg, opt, mesh_a)
    assert isinstance(opt_state, MasterState)
    step_a = make_jitted_train_step(cfg, opt, mesh_a)
    params, opt_state, _ = _train(params, opt_state, step_a, tokens, 2)

    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        mgr.save(params, opt_state, step=2)
        _, _, ref_losses = _train(params, opt_state, step_a, tokens, 1)

        params_t, opt_t = init_sharded_state(jax.random.key(9), cfg, opt, None)
        restored = mgr.restore(params_t, opt_t)
        assert restored is not None
        r_params, r_opt, _ = restored
        assert r_params["layers"]["wq"].dtype == jnp.bfloat16
        assert isinstance(r_opt, MasterState) or "master" in str(type(r_opt))
        step_b = make_jitted_train_step(cfg, opt, None)
        _, _, losses = _train(r_params, r_opt, step_b, tokens, 1)
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-3, atol=2e-3)
        mgr.close()


def test_async_saves_join_before_restore(tmp_path):
    """Async checkpointing: back-to-back non-blocking saves serialize in
    the background; restore() joins in-flight work first and sees the
    LAST save's values exactly."""
    d = str(tmp_path / "ck")
    mgr = CheckpointManager(d)
    p1 = {"w": jax.numpy.ones((8, 8))}
    p2 = {"w": jax.numpy.full((8, 8), 3.0)}
    opt = {"mu": jax.numpy.zeros((8, 8))}
    mgr.save(p1, opt, 1)          # async
    mgr.save(p2, opt, 2)          # joins save 1, dispatches save 2 async
    out = mgr.restore(p1, opt)    # joins save 2 before reading
    assert out is not None
    params, _, step = out
    assert step == 2
    np.testing.assert_array_equal(np.asarray(params["w"]), 3.0)
    mgr.close()
