"""Per-request seeds: reproducible sampling independent of batch
composition, slot placement, and engine mode.  Seeded rows key each draw
off fold_in(key(seed), position) — the SAME key in the sequential chunk,
the speculative verify pass, and the admission prefill — so a seeded
sampled request is deterministic everywhere.
"""

import jax

from elastic_gpu_scheduler_tpu.models.serving import InferenceEngine, Request
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype="float32",
)
PARAMS = init_params(jax.random.key(0), CFG)


def run_one(prompt, seed, companions=(), **kw):
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=4, max_len=48, page_size=8, fused_steps=4,
        **kw,
    )
    others = [
        eng.submit(Request(prompt=list(c), max_new_tokens=6))
        for c in companions
    ]
    r = eng.submit(Request(prompt=list(prompt), max_new_tokens=8,
                           temperature=0.9, seed=seed))
    eng.run_until_idle()
    for o in others:
        assert not o.error
    assert not r.error, r.error
    return r.output


def test_seed_reproducible_across_batch_composition():
    alone = run_one([5, 17, 3], seed=1234)
    crowded = run_one([5, 17, 3], seed=1234,
                      companions=([60, 2], [9, 9, 9], [1, 2, 3, 4]))
    assert alone == crowded
    assert run_one([5, 17, 3], seed=1234) == alone  # restart-stable
    assert run_one([5, 17, 3], seed=99) != alone  # seeds differentiate


def test_seed_identical_under_speculation():
    """A seeded SAMPLED request produces the same tokens in speculative
    and sequential engines (position-keyed draws)."""
    seq = run_one([5, 17, 3], seed=7)
    spec = run_one([5, 17, 3], seed=7, spec_k=3)
    assert seq == spec


def test_seed_with_filters():
    a = run_one([5, 17, 3], seed=42)
    # engage the filtered sampling variant via top_k on a companion —
    # the seeded row's draws must not change
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=2, max_len=48, page_size=8, fused_steps=4,
    )
    c = eng.submit(Request(prompt=[60, 2], max_new_tokens=6,
                           temperature=0.8, top_k=5))
    r = eng.submit(Request(prompt=[5, 17, 3], max_new_tokens=8,
                           temperature=0.9, seed=42))
    eng.run_until_idle()
    assert not r.error and not c.error
    assert r.output == a
