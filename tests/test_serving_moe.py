"""MoE serving in the paged engine (VERDICT r2 #3).

The engine's MoE FFN is drop-free (serving._moe_ffn_serve): unlike
training's capacity-factor ``moe_ffn``, a token's routing never depends on
which other requests share the batch.  Correctness bar: engine outputs ==
solo ``generate()`` runs, across the MoE × int8-KV × prefix-cache matrix.

The reference has no serving plane at all (SURVEY §2 #19).
"""

import jax
import numpy as np
import pytest

from elastic_gpu_scheduler_tpu.models.generate import generate
from elastic_gpu_scheduler_tpu.models.serving import InferenceEngine, Request
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

# capacity_factor == n_experts makes training's moe_ffn capacity equal the
# token count, so the generate() oracle is drop-free too and the two
# computations agree exactly (the engine path is ALWAYS drop-free)
MOE_CFG = TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype="float32", n_experts=4, capacity_factor=4.0,
)
PARAMS = init_params(jax.random.key(1), MOE_CFG)
# sharpen the router: at init-scale weights, routing argmax margins sit
# within int8-KV quantization noise, so the int8 matrix cells would test
# near-tie coin flips instead of engine/oracle equivalence
PARAMS["layers"]["moe_gate"] = PARAMS["layers"]["moe_gate"] * 8.0


def _expert_spread(params, prompts):
    """The test is vacuous if every token routes to one expert — assert the
    router actually spreads tokens at these scales."""
    import jax.numpy as jnp

    from elastic_gpu_scheduler_tpu.models.quantize import wmat

    toks = jnp.asarray([t for p in prompts for t in p], jnp.int32)
    x = params["embed"][toks]
    gates = x @ wmat(params["layers"]["moe_gate"][0], x.dtype)
    return len(set(np.asarray(jnp.argmax(gates, -1)).tolist()))


@pytest.mark.parametrize("kv_int8", [False, True])
@pytest.mark.parametrize("prefix_cache", [False, True])
def test_moe_engine_matches_generate(kv_int8, prefix_cache):
    prompts = [[5, 17, 3], [60, 2], [9, 9, 9, 9], list(range(1, 20))]
    assert _expert_spread(PARAMS, prompts) >= 2
    engine = InferenceEngine(
        PARAMS, MOE_CFG, max_batch=4, max_len=48, page_size=8,
        kv_int8=kv_int8, prefix_cache=prefix_cache,
    )
    reqs = [
        engine.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts
    ]
    engine.run_until_idle()
    for p, req in zip(prompts, reqs):
        assert req.done.is_set() and not req.error
        ref = generate(
            PARAMS, jax.numpy.asarray([p]), MOE_CFG, max_new_tokens=6
        )
        np.testing.assert_array_equal(
            np.asarray(ref)[0, len(p):], req.output
        )


def test_moe_prefix_cache_hit_matches_cold():
    """A prefix-cache hit skips the matched pages; the remainder must still
    route through the experts identically."""
    prompt = list(range(1, 18))  # 2 full pages at page_size=8
    eng = InferenceEngine(
        PARAMS, MOE_CFG, max_batch=2, max_len=48, page_size=8,
        prefix_cache=True,
    )
    a = eng.submit(Request(prompt=prompt, max_new_tokens=8))
    eng.run_until_idle()
    hits0 = eng.prefix_hit_tokens
    b = eng.submit(Request(prompt=prompt, max_new_tokens=8))
    eng.run_until_idle()
    assert eng.prefix_hit_tokens > hits0  # the second run actually hit
    assert a.output == b.output


def test_moe_int8_weights_serve():
    """MoE expert weights quantize (expert-stacked (E,D,F) leaves) and the
    engine serves the quantized model end to end."""
    from elastic_gpu_scheduler_tpu.models.quantize import quantize_params

    qparams = quantize_params(PARAMS)
    eng = InferenceEngine(qparams, MOE_CFG, max_batch=2, max_len=32)
    r = eng.submit(Request(prompt=[5, 17, 3], max_new_tokens=6))
    eng.run_until_idle()
    assert r.done.is_set() and not r.error
    ref = generate(
        qparams, jax.numpy.asarray([[5, 17, 3]]), MOE_CFG, max_new_tokens=6
    )
    np.testing.assert_array_equal(np.asarray(ref)[0, 3:], r.output)


def test_moe_engine_with_speculation_matches_generate():
    """spec_k > 0 composes with MoE serving: the verify chunk routes
    through the same drop-free expert FFN, so outputs stay equal to the
    dense-path oracle."""
    engine = InferenceEngine(
        PARAMS, MOE_CFG, max_batch=4, max_len=48, page_size=8, spec_k=3,
    )
    prompts = [[5, 17, 3], [9, 9, 9, 9], list(range(1, 20))]
    reqs = [
        engine.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts
    ]
    engine.run_until_idle()
    assert engine.spec_passes > 0
    for p, req in zip(prompts, reqs):
        assert req.done.is_set() and not req.error
        ref = generate(
            PARAMS, jax.numpy.asarray([p]), MOE_CFG, max_new_tokens=6
        )
        np.testing.assert_array_equal(np.asarray(ref)[0, len(p):], req.output)


# -- MoE on a mesh (VERDICT r3 #3) -------------------------------------------


def _moe_engine_tokens(prompts, **kw):
    engine = InferenceEngine(
        PARAMS, MOE_CFG, max_batch=4, max_len=48, page_size=8, **kw
    )
    reqs = [
        engine.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts
    ]
    engine.run_until_idle()
    for r in reqs:
        assert r.done.is_set() and not r.error, r.error
    return [r.output for r in reqs], engine


@pytest.mark.parametrize(
    "axes", [dict(tensor=2), dict(expert=2), dict(expert=2, tensor=2)],
    ids=lambda a: "x".join(f"{k}{v}" for k, v in sorted(a.items())),
)
def test_moe_engine_on_mesh_matches_single_device(axes):
    """MoE serving over a mesh — tensor-sharded expert FFNs, true expert
    parallelism (expert axis), and both at once — must be token-identical
    to the single-device engine.  Sharding is placement, never behavior."""
    from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

    prompts = [[5, 17, 3], [60, 2], [9, 9, 9, 9], list(range(1, 20))]
    want, _ = _moe_engine_tokens(prompts)
    n = int(np.prod(list(axes.values())))
    mesh = make_mesh(MeshSpec(**axes), jax.devices()[:n])
    got, eng = _moe_engine_tokens(prompts, mesh=mesh)
    assert got == want
    # expert weights measurably sharded, not replicated
    for name in ("w_gate", "w_in", "w_out"):
        arr = eng.params["layers"][name]
        assert not arr.sharding.is_fully_replicated, (name, arr.sharding)


def test_moe_mesh_expert_weights_sharded_on_expert_axis():
    """expert=2: each rank holds HALF the experts (the checkpoint-bigger-
    than-one-chip case MoE exists for), not a full replica."""
    from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(expert=2), jax.devices()[:2])
    _, eng = _moe_engine_tokens([[5, 17, 3]], mesh=mesh)
    w = eng.params["layers"]["w_gate"]  # (L, E, D, F)
    (shard,) = {s.data.shape for s in w.addressable_shards}
    assert shard[1] == w.shape[1] // 2, (shard, w.shape)


def test_moe_engine_mesh_with_speculation():
    """MoE × mesh × spec_k: the composed production path."""
    from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

    prompts = [[5, 17, 3, 5, 17, 3, 5, 17], [60, 2] * 6]
    want, _ = _moe_engine_tokens(prompts, spec_k=3)
    mesh = make_mesh(MeshSpec(expert=2, tensor=2), jax.devices()[:4])
    got, _ = _moe_engine_tokens(prompts, mesh=mesh, spec_k=3)
    assert got == want


def test_moe_grouped_matmul_prefill_matches_generate():
    """Prompts past the decode-size threshold run the grouped-matmul
    (lax.ragged_dot) dispatch — dense FLOPs per token instead of the old
    E× mask dispatch — and must still match the generate() oracle."""
    prompts = [list(np.random.default_rng(3).integers(1, 60, 40)),
               [5, 17, 3]]
    assert _expert_spread(PARAMS, prompts) >= 2
    engine = InferenceEngine(
        PARAMS, MOE_CFG, max_batch=2, max_len=64, page_size=8
    )
    reqs = [
        engine.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts
    ]
    engine.run_until_idle()
    for r, p in zip(reqs, prompts):
        assert not r.error, r.error
        ref = generate(
            PARAMS, jax.numpy.asarray([p]), MOE_CFG, max_new_tokens=6
        )
        np.testing.assert_array_equal(np.asarray(ref)[0, len(p):], r.output)


def test_moe_grouped_matmul_on_tensor_mesh():
    """The ragged_dot dispatch under tensor sharding (F over tensor):
    long-prompt MoE on a tensor=2 mesh stays token-identical."""
    from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

    prompts = [list(np.random.default_rng(4).integers(1, 60, 40))]
    want, _ = _moe_engine_tokens(prompts)
    mesh = make_mesh(MeshSpec(tensor=2), jax.devices()[:2])
    got, _ = _moe_engine_tokens(prompts, mesh=mesh)
    assert got == want
