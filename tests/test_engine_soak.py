"""Serving-engine soak: page-accounting exactness and bounded host heap
under a mixed churning workload (VERDICT r3 #7), plus the draft-cache
page-pressure interaction and HBM envelope accounting (VERDICT r3 #5).

The scheduler has 300-step churn with invariants (test_soak.py); this is
the serving-side analogue.  Invariants are checked between waves — any
page leak or ref-count drift fails an assertion, never just an output
diff.
"""

import tracemalloc
from collections import Counter

import jax
import numpy as np

from elastic_gpu_scheduler_tpu.models.lora import lora_init
from elastic_gpu_scheduler_tpu.models.serving import (
    InferenceEngine,
    Request,
    estimate_hbm_bytes,
)
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype="float32",
)
PARAMS = init_params(jax.random.key(0), CFG)


def check_page_accounting(eng):
    """Every page is in exactly one place; refcounts equal live holders.

    Partition of the n_pages-1 real pages (scratch excluded):
    free ∪ slot-held ∪ prefix-cached, with slot-held ∩ cached allowed
    (a cached page a live slot shares) and free disjoint from both."""
    n_real = eng.n_pages - 1
    free = eng.free_pages
    assert len(set(free)) == len(free), "duplicate pages on the free list"
    free = set(free)
    held = Counter(pg for sp in eng.slot_pages for pg in sp)
    cached = set(eng.page_key)
    assert not free & set(held), "page simultaneously free and slot-held"
    assert not free & cached, "page simultaneously free and prefix-cached"
    for pg in range(1, eng.n_pages):
        assert eng.page_ref[pg] == held.get(pg, 0), (
            f"page {pg}: ref {eng.page_ref[pg]} != holders {held.get(pg, 0)}"
        )
    accounted = free | set(held) | cached
    assert len(accounted) == n_real, (
        f"leak: {n_real - len(accounted)} pages unaccounted "
        f"(free={len(free)} held={len(held)} cached={len(cached)})"
    )
    # prefix bookkeeping is a bijection
    assert len(eng.prefix_entries) == len(eng.page_key)
    for key, pg in eng.prefix_entries.items():
        assert eng.page_key.get(pg) == key


def _adapters():
    lo = lora_init(jax.random.key(5), PARAMS, rank=2, targets=("wq", "wv"))
    for t, ab in lo["adapters"].items():
        lo["adapters"][t]["b"] = (
            jax.random.normal(jax.random.key(6), ab["b"].shape) * 0.08
        )
    return {"style": lo}


def test_engine_soak_mixed_workload():
    """~120 requests churn through speculation + prefix cache + multi-LoRA
    + stop tokens + sampling + cancellation with the pool near capacity;
    page accounting stays exact and the host heap growth stays bounded."""
    rng = np.random.default_rng(42)
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=4, max_len=48, page_size=8,
        n_pages=17,  # 16 real pages vs 4 slots × 6 pages peak → pressure
        fused_steps=4, spec_k=2, prefix_cache=True, adapters=_adapters(),
        prefill_chunk=8, logprobs_k=3,
    )
    shared_prefix = [7, 8, 9, 10, 11, 12, 13, 14]  # one full page
    waves_done = 0
    tracemalloc.start()
    baseline = None
    for wave in range(10):
        reqs = []
        for j in range(12):
            kind = rng.integers(0, 5)
            prompt = (
                shared_prefix + [int(rng.integers(1, 60))]
                if kind <= 1 else
                [int(t) for t in rng.integers(1, 60, rng.integers(2, 20))]
            )
            extra = int(rng.integers(0, 5))
            r = Request(
                prompt=prompt,
                max_new_tokens=int(rng.integers(2, 14)),
                temperature=0.7 if kind == 2 else 0.0,
                stop_tokens=(3, 5) if kind == 3 else (),
                adapter="style" if kind == 4 else "",
                # round-4 per-request features churn alongside (each wave
                # mixes them arbitrarily so every chunk-variant pair and
                # bias/penalty row lifecycle gets exercised)
                logprobs=2 if extra == 0 else 0,
                logit_bias={int(rng.integers(1, 60)): 3.0}
                if extra == 1 else {},
                frequency_penalty=0.8 if extra == 2 else 0.0,
                seed=int(rng.integers(0, 1 << 31))
                if extra == 3 and kind == 2 else None,
                min_tokens=2 if extra == 4 else 0,
            )
            reqs.append(eng.submit(r))
        # cancel a couple mid-flight-ish (engine checks at chunk bounds)
        reqs[3].cancel()
        reqs[7].cancel()
        eng.run_until_idle(max_steps=100_000)
        for r in reqs:
            assert r.done.is_set(), "request stalled forever"
            assert not r.error, r.error
            if r.logprobs:  # lockstep invariant across all emission paths
                assert len(r.token_logprobs) == len(r.output)
                assert len(r.top_logprobs) == len(r.output)
        check_page_accounting(eng)
        # per-slot feature state fully reset after drain
        assert not eng._bias_set.any() and not eng._seeded.any()
        assert not eng.prefilling.any()
        waves_done += 1
        if wave == 1:  # after warm-up (compiles, caches) stabilizes
            baseline = tracemalloc.get_traced_memory()[0]
    growth = tracemalloc.get_traced_memory()[0] - baseline
    tracemalloc.stop()
    assert waves_done == 10
    # 8 waves of churn after the baseline snapshot must not accumulate
    # host-side state: prefix cache is bounded by the pool, slots reset.
    assert growth < 8 * 1024 * 1024, f"host heap grew {growth/1e6:.1f}MB"


def test_draft_page_pressure_stall_resume():
    """VERDICT r3 #5: draft-model speculation + pool exhaustion.  Slots
    stall when the TARGET pool runs dry while the draft keeps its dense
    cache; on release the stalled slots must resume, complete, and leave
    exact page accounting (no draft/target interaction leak)."""
    dcfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype="float32",
    )
    dparams = init_params(jax.random.key(9), dcfg)
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=3, max_len=32, page_size=8,
        n_pages=7,  # 6 real pages; 3 slots × 4-page peak cannot coexist
        fused_steps=4, spec_k=2, draft=(dparams, dcfg),
    )
    reqs = [
        eng.submit(Request(prompt=[7, 8, 9], max_new_tokens=12)),
        eng.submit(Request(prompt=[11, 12], max_new_tokens=12)),
        eng.submit(Request(prompt=[21, 22, 23, 24], max_new_tokens=12)),
    ]
    eng.run_until_idle(max_steps=100_000)
    for r in reqs:
        assert r.done.is_set() and not r.error, r.error
        assert len(r.output) == 12
    check_page_accounting(eng)
    # freed slots reset their draft ingestion counter — the reset is what
    # keeps a recycled slot from attending a dead tenant's draft rows
    freed = [i for i, s in enumerate(eng.slots) if s is None]
    assert freed and all(eng.draft_len[i] == 0 for i in freed)
    # non-speculative engine agrees (stall/resume is invisible in outputs)
    plain = InferenceEngine(
        PARAMS, CFG, max_batch=3, max_len=32, page_size=8, fused_steps=4
    )
    want = []
    for p in ([7, 8, 9], [11, 12], [21, 22, 23, 24]):
        r = plain.submit(Request(prompt=list(p), max_new_tokens=12))
        plain.run_until_idle()
        want.append(r.output)
    assert [r.output for r in reqs] == want


def test_hbm_envelope_production_shapes():
    """VERDICT r3 #5: the stated memory envelope at production shapes.
    A 7B-class target (int8 weights), int8 KV pool at B=8 × 8k context,
    plus a 160M-class bf16 draft and its dense cache must fit a v5e chip
    (16 GiB) with headroom — and the draft cache share stays minor (the
    'page the draft cache' alternative buys little at these shapes)."""
    target = TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=11008, dtype="bfloat16",
    )
    draft = TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=8, n_heads=8,
        n_kv_heads=8, d_ff=2752, dtype="bfloat16",
    )
    acct = estimate_hbm_bytes(
        target, max_batch=8, max_len=8192, page_size=64,
        kv_int8=True, draft_cfg=draft, param_bytes_per=1.0,  # int8 weights
    )
    GiB = 1 << 30
    # measured: pool 4.1 + target-int8 5.5 + draft cache 2.0 + draft 0.3
    # ≈ 12.0 GiB — fits 16 GiB with ~4 GiB activation headroom.  The
    # draft cache is a REAL tenant (half the pool's size) — if shapes
    # grow past this envelope, paging the draft cache is the next move;
    # this test is the tripwire that makes that growth loud.
    assert acct["total"] < 14 * GiB, {k: v / GiB for k, v in acct.items()}
    assert acct["draft_cache_bytes"] < acct["kv_pool_bytes"], acct


def test_cfg_param_count_matches_real_params():
    """_cfg_param_count (the HBM estimator's shape arithmetic) must track
    init_params exactly — otherwise the envelope tripwire drifts."""
    from elastic_gpu_scheduler_tpu.models.serving import _cfg_param_count
    from elastic_gpu_scheduler_tpu.models.transformer import param_count

    for cfg in (
        CFG,
        TransformerConfig(
            vocab_size=97, d_model=48, n_layers=3, n_heads=4, n_kv_heads=2,
            d_ff=96, dtype="float32",
        ),
        TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            dtype="float32", n_experts=4, capacity_factor=4.0,
        ),
    ):
        real = param_count(init_params(jax.random.key(0), cfg))
        assert _cfg_param_count(cfg) == real, (cfg, _cfg_param_count(cfg), real)


def test_priority_spill_churn_soak():
    """Priority/spill soak (round 5): waves of mixed-priority requests
    at heavy page pressure — spills, resumes, queue-cap rejections and
    last-resort pool preemptions all churning together — with the exact
    page-accounting partition checked between waves and every surviving
    request's floor/length contract intact."""
    rng = np.random.default_rng(11)
    eng = InferenceEngine(
        PARAMS, CFG, max_batch=3, max_len=64, page_size=8, n_pages=7,
        fused_steps=2, prefix_cache=True, max_queue=8,
    )
    from elastic_gpu_scheduler_tpu.models.serving import QUEUE_FULL_ERROR
    from elastic_gpu_scheduler_tpu.server.inference import EngineLoop

    # the production driver: EngineLoop owns the last-resort pool
    # preemption that total exhaustion falls back to
    loop = EngineLoop(eng).start()
    completed = rejected = preempted = 0
    for wave in range(8):
        reqs = []
        for k in range(6):
            pri = int(rng.integers(-1, 3))
            n_new = int(rng.integers(8, 25))
            plen = int(rng.integers(4, 13))
            reqs.append(eng.submit(Request(
                prompt=[int(t) for t in rng.integers(0, 64, plen)],
                max_new_tokens=n_new,
                priority=pri,
                temperature=0.7 if k % 3 == 0 else 0.0,
                seed=int(wave * 10 + k) if k % 3 == 0 else None,
            )))
        if wave % 3 == 1:
            reqs[2].cancel()  # churn the cancel path too
        for r in reqs:
            assert r.done.wait(timeout=180), "request never finished"
        for r in reqs:
            if r.error == QUEUE_FULL_ERROR:
                rejected += 1
            elif "preempted" in (r.error or ""):
                preempted += 1
            elif not r.error and not r.cancelled:
                completed += 1
                assert 1 <= len(r.output) <= r.max_new_tokens
        # quiesce the loop before auditing shared page state
        for _ in range(2000):
            if not any(s is not None for s in eng.slots) and eng.queue.empty():
                break
            import time as _t
            _t.sleep(0.005)
        check_page_accounting(eng)
    loop.stop()
    assert completed >= 20, (completed, rejected, preempted, eng.spills)
    # the soak actually exercised the pressure machinery
    assert eng.spills >= 1 or preempted >= 1 or rejected >= 1, (
        eng.spills, preempted, rejected
    )
