"""North-star end-to-end test: a pod is scheduled through the extender HTTP
stack, bound with coordinate annotations, and the launcher turns that
allocation into a mesh and trains — plus checkpoint/resume."""

import json
import tempfile
import urllib.request

import jax
import numpy as np
import pytest

from elastic_gpu_scheduler_tpu.cli import build_stack
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster
from elastic_gpu_scheduler_tpu.k8s.objects import (
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.launcher import JobSpec, run_job
from elastic_gpu_scheduler_tpu.models.transformer import TransformerConfig
from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec
from elastic_gpu_scheduler_tpu.server.routes import ExtenderServer
from elastic_gpu_scheduler_tpu.utils import consts

TINY = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64, dtype="float32"
)


def post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _schedule_and_bind(pod_name: str, container: str) -> dict:
    """Drive a 4-chip pod through the extender HTTP stack on a 2x2 v5e
    host; returns the bound pod's annotations (asserted non-empty for the
    container — the coordinates ARE the product under test)."""
    cluster = FakeCluster()
    cluster.add_node(
        make_tpu_node(
            "tpu-host", chips=4, hbm_gib=64, accelerator="v5e",
            slice_topology="2x2", host_topology="2x2", host_offset="0.0",
        )
    )
    clientset = FakeClientset(cluster)
    registry, predicate, prioritize, bind, controller, status, gang = build_stack(
        clientset, cluster=cluster, priority="ici-locality"
    )
    server = ExtenderServer(predicate, prioritize, bind, status, host="127.0.0.1", port=0)
    port = server.start()
    try:
        pod = make_pod(
            pod_name,
            containers=[
                Container(
                    name=container,
                    resources=ResourceRequirements(
                        limits={consts.RESOURCE_TPU_CORE: 400}
                    ),
                )
            ],
        )
        cluster.create_pod(pod)
        filt = post(port, "/scheduler/filter",
                    {"Pod": pod.to_dict(), "NodeNames": ["tpu-host"]})
        assert filt["NodeNames"] == ["tpu-host"]
        res = post(port, "/scheduler/bind", {
            "PodName": pod_name, "PodNamespace": "default",
            "PodUID": pod.metadata.uid, "Node": "tpu-host",
        })
        assert res["Error"] == ""
        ann = cluster.get_pod("default", pod_name).metadata.annotations
        assert ann[consts.ANNOTATION_CONTAINER_PREFIX + container]
        return ann
    finally:
        server.stop()


def test_schedule_then_launch_end_to_end():
    """BASELINE north star: placed, bound, and launched — no GPU in the loop."""
    ann = _schedule_and_bind("trainer", "main")

    # launch: 4 allocated chips → data=1, tensor=2, seq=2 mesh on CPU devices
    spec = JobSpec(
        model=TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            dtype="float32", use_ring_attention=True,
        ),
        mesh=MeshSpec(tensor=2, seq=2),
        steps=4,
        batch_size=4,
        seq_len=32,
        lr=1e-2,
    )
    losses = run_job(spec, pod_annotations=ann, container="main",
                     devices=jax.devices()[:4])
    assert len(losses) == 4
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_checkpoint_resume():
    with tempfile.TemporaryDirectory() as d:
        spec = JobSpec(
            model=TINY, mesh=MeshSpec(), steps=3, batch_size=2, seq_len=16,
            checkpoint_dir=d, checkpoint_every=1, lr=1e-2,
        )
        losses_a = run_job(spec, devices=jax.devices()[:1])
        assert len(losses_a) == 3
        # resume: steps already complete → no further work
        spec2 = JobSpec(
            model=TINY, mesh=MeshSpec(), steps=5, batch_size=2, seq_len=16,
            checkpoint_dir=d, checkpoint_every=1, lr=1e-2,
        )
        losses_b = run_job(spec2, devices=jax.devices()[:1])
        assert len(losses_b) == 2  # resumed at step 3, ran 3..4


def test_launcher_env_fallback(monkeypatch):
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0.0,0.1")
    spec = JobSpec(model=TINY, mesh=MeshSpec(tensor=2), steps=2,
                   batch_size=2, seq_len=16)
    losses = run_job(spec, devices=jax.devices()[:2])
    assert len(losses) == 2 and np.isfinite(losses).all()


def test_schedule_then_serve_end_to_end():
    """The deploy/tpu-inference-server.yaml loop, in-process: an inference
    pod is scheduled through the extender HTTP stack (4-chip contiguous
    sub-box, coordinate annotations), the placement becomes a tensor
    mesh, and the paged engine serves requests over it — token-identical
    to a single-device engine."""
    from elastic_gpu_scheduler_tpu.models.serving import (
        InferenceEngine,
        Request,
    )
    from elastic_gpu_scheduler_tpu.models.transformer import init_params
    from elastic_gpu_scheduler_tpu.parallel.mesh import mesh_from_allocation

    ann = _schedule_and_bind("inference-server", "server")

    # the pod's 4 allocated chips → a tensor=4 serving mesh
    mesh = mesh_from_allocation(
        ann, "server", MeshSpec(tensor=4), devices=jax.devices()[:4]
    )
    cfg = TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype="float32",
    )
    params = init_params(jax.random.key(2), cfg)
    prompts = [[5, 17, 3], [60, 2, 9, 9]]

    def run(mesh_arg):
        eng = InferenceEngine(
            params, cfg, max_batch=2, max_len=48, page_size=8,
            mesh=mesh_arg,
        )
        reqs = [
            eng.submit(Request(prompt=list(p), max_new_tokens=8))
            for p in prompts
        ]
        eng.run_until_idle()
        for r in reqs:
            assert r.done.is_set() and not r.error, r.error
        return [r.output for r in reqs]

    assert run(mesh) == run(None)


def test_multislice_gang_launches_hierarchical_mesh():
    """Config-E end to end (VERDICT r4 #3): a gang forced to straddle two
    slices is scheduled + bound through the stack, its members' ledgers
    carry the DCN boundary, and run_job builds the hierarchical mesh
    (data axis across slices over DCN, fsdp/tensor inside a slice) and
    trains to finite decreasing loss on 8 virtual devices."""
    import threading

    from elastic_gpu_scheduler_tpu.k8s.extender import (
        ExtenderArgs,
        ExtenderBindingArgs,
    )

    cluster = FakeCluster()
    for sname in ["ms-a", "ms-b"]:
        cluster.add_node(
            make_tpu_node(
                f"{sname}-h0", chips=4, hbm_gib=64, accelerator="v5e",
                slice_topology="2x2", host_topology="2x2", host_offset="0.0",
                slice_name=sname,
            )
        )
    clientset = FakeClientset(cluster)
    registry, predicate, prioritize, bind, controller, status, gang = build_stack(
        clientset, cluster=cluster, priority="ici-locality", gang_timeout=5.0,
    )
    nodes = [n.metadata.name for n in cluster.list_nodes()]
    pods = []
    for i in range(2):
        p = make_pod(
            f"ms-{i}",
            containers=[
                Container(
                    name="main",
                    resources=ResourceRequirements(
                        limits={consts.RESOURCE_TPU_CORE: 400}
                    ),
                )
            ],
            annotations={
                consts.ANNOTATION_GANG_NAME: "msgang",
                consts.ANNOTATION_GANG_SIZE: "2",
            },
        )
        cluster.create_pod(p)
        pods.append(p)

    def member(p):
        filt = predicate.handle(ExtenderArgs(pod=p, node_names=list(nodes)))
        assert filt.node_names, filt.failed_nodes
        res = bind.handle(ExtenderBindingArgs(
            pod_name=p.metadata.name, pod_namespace=p.metadata.namespace,
            pod_uid=p.metadata.uid, node=filt.node_names[0],
        ))
        assert not res.error, res.error

    threads = [threading.Thread(target=member, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)

    ann = cluster.get_pod("default", "ms-0").metadata.annotations
    assert ann[consts.ANNOTATION_GANG_SLICES] == "ms-a,ms-b"

    # the job side: 8 virtual devices standing in for the gang's 2x4
    # chips; data=2 spans the two slices, fsdp=2 x tensor=2 stay inside
    spec = JobSpec(
        model=TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            dtype="float32",
        ),
        mesh=MeshSpec(data=2, fsdp=2, tensor=2),
        steps=4,
        batch_size=8,
        seq_len=32,
        lr=1e-2,
    )
    losses = run_job(spec, pod_annotations=ann, container="main",
                     devices=jax.devices()[:8])
    assert len(losses) == 4
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
