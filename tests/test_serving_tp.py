"""Tensor-parallel serving: the paged engine on a mesh with a ``tensor``
axis must produce the same tokens as the single-device engine — sharding is
a placement concern, never a behavior change.

The reference has no serving plane (SURVEY §2 #19); TP serving is the
"checkpoint bigger than one chip's HBM" requirement of a TPU framework.
"""

import jax
import numpy as np
import pytest

from elastic_gpu_scheduler_tpu.models.serving import InferenceEngine, Request
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

CFG = TransformerConfig(
    vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, dtype="float32",
)
PARAMS = init_params(jax.random.key(2), CFG)
PROMPTS = [[5, 17, 3], [60, 2, 9, 9], list(range(1, 17)), [42]]


def run_engine(**kw):
    eng = InferenceEngine(PARAMS, CFG, max_batch=4, max_len=64, page_size=8,
                          **kw)
    reqs = [eng.submit(Request(prompt=p, max_new_tokens=8)) for p in PROMPTS]
    eng.run_until_idle()
    for r in reqs:
        assert r.done.is_set() and not r.error, r.error
    return [r.output for r in reqs]


@pytest.mark.parametrize("axes", [dict(tensor=2), dict(data=2, tensor=2)])
def test_tp_engine_matches_single_device(axes):
    baseline = run_engine()
    mesh = make_mesh(MeshSpec(**axes), jax.devices()[: np.prod(list(axes.values()))])
    got = run_engine(mesh=mesh)
    assert got == baseline


def test_tp_engine_weights_actually_sharded():
    mesh = make_mesh(MeshSpec(tensor=2), jax.devices()[:2])
    eng = InferenceEngine(PARAMS, CFG, max_batch=2, max_len=32, page_size=8,
                          mesh=mesh)
    wq = eng.params["layers"]["wq"]
    assert not wq.sharding.is_fully_replicated, wq.sharding
    # kv pool: head axis (2 kv heads) sharded over tensor=2
    assert not eng.kv["k"].sharding.is_fully_replicated, eng.kv["k"].sharding


def test_tp_engine_int8_kv_and_odd_heads_fall_back():
    """kv_heads not divisible by tensor → replicated pool, same outputs."""
    cfg = TransformerConfig(
        vocab_size=97, d_model=48, n_layers=2, n_heads=3, d_ff=96,
        dtype="float32",
    )
    params = init_params(jax.random.key(3), cfg)

    def run(mesh=None):
        eng = InferenceEngine(params, cfg, max_batch=2, max_len=32,
                              page_size=8, kv_int8=True, mesh=mesh)
        reqs = [eng.submit(Request(prompt=p, max_new_tokens=6))
                for p in PROMPTS[:2]]
        eng.run_until_idle()
        for r in reqs:
            assert r.done.is_set() and not r.error, r.error
        return [r.output for r in reqs]

    mesh = make_mesh(MeshSpec(tensor=2), jax.devices()[:2])
    assert run(mesh) == run()


def test_tp_mesh_requires_tensor_axis():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    with pytest.raises(ValueError, match="tensor"):
        InferenceEngine(PARAMS, CFG, max_batch=2, mesh=mesh)


def test_tp_engine_with_paged_kernel_matches_single_device():
    """Round 4 (VERDICT r3 #2d): the Pallas paged kernel under a mesh —
    shard_mapped over the tensor axis on the head dims — must reproduce
    the single-device gather engine's tokens exactly."""
    baseline = run_engine()
    mesh = make_mesh(MeshSpec(tensor=2), jax.devices()[:2])
    got = run_engine(mesh=mesh, paged_kernel=True)
    assert got == baseline


def test_tp_engine_paged_kernel_speculative():
    """kernel + mesh + spec_k all at once: the full production combo."""
    baseline = run_engine(spec_k=3)
    mesh = make_mesh(MeshSpec(tensor=2), jax.devices()[:2])
    got = run_engine(mesh=mesh, paged_kernel=True, spec_k=3)
    assert got == baseline
