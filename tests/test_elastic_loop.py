"""The full elastic loop, end to end (VERDICT r4 #6): a gang-scheduled
"training job" loses a node mid-run, the controller frees its capacity
through the real watch stream, the job reschedules onto the surviving
node, and training resumes from checkpoint on the SMALLER mesh with a
continuous loss trajectory.

Every piece already exists separately (controller release on delete:
test_e2e_wire; gang planning: test_gang; elastic orbax resume across mesh
shapes: test_elastic_resume); this composes them through the production
stack — mini API server, REST clientset + watch view, extender HTTP
server, reconciliation controller, launcher."""

import tempfile
import threading

import jax
import numpy as np

from test_e2e_wire import (
    K8sApiServer,
    KubeSchedulerClient,
    used_core,
)
from conftest import poll

from elastic_gpu_scheduler_tpu.cli import build_stack
from elastic_gpu_scheduler_tpu.k8s.client import RestClientset, RestClusterView
from elastic_gpu_scheduler_tpu.k8s.objects import (
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.launcher import JobSpec, run_job
from elastic_gpu_scheduler_tpu.models.transformer import TransformerConfig
from elastic_gpu_scheduler_tpu.parallel.mesh import MeshSpec
from elastic_gpu_scheduler_tpu.server.routes import ExtenderServer
from elastic_gpu_scheduler_tpu.utils import consts

TINY = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype="float32",
)


def gang_pod(name, gang, size, core):
    return make_pod(
        name,
        containers=[
            Container(
                name="main",
                resources=ResourceRequirements(
                    limits={consts.RESOURCE_TPU_CORE: core}
                ),
            )
        ],
        annotations={
            consts.ANNOTATION_GANG_NAME: gang,
            consts.ANNOTATION_GANG_SIZE: str(size),
        },
        uid=f"uid-{name}",
    )


def test_node_death_replan_resume_end_to_end():
    api = K8sApiServer()
    for i in range(2):
        api.add_node(
            make_tpu_node(f"n{i}", chips=4, hbm_gib=64, accelerator="v5e")
        )
    rest = RestClientset(base_url=f"http://127.0.0.1:{api.port}")
    view = RestClusterView(rest, reconnect_delay=0.1)
    registry, predicate, prioritize, bind, controller, status, gang = (
        build_stack(rest, cluster=view, priority="binpack", gang_timeout=15.0)
    )
    controller.resync_period = 0.3
    controller.start()
    server = ExtenderServer(
        predicate, prioritize, bind, status, host="127.0.0.1", port=0,
        workers=8,
    )
    port = server.start()
    ks = KubeSchedulerClient(port)
    try:
        # 1. gang-schedule the 2-member job (one whole node each) through
        # the wire — all-or-nothing barrier, so both bind concurrently
        pods = [gang_pod(f"train-{i}", "elastic-job", 2, 400)
                for i in range(2)]
        docs = [api.create_pod(p) for p in pods]
        errs = []

        def member(doc):
            node = ks.schedule(doc, ["n0", "n1"])
            res = ks.bind(doc, node)
            if res.get("Error"):
                errs.append(res["Error"])

        ts = [threading.Thread(target=member, args=(d,)) for d in docs]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not errs, errs
        assert used_core(registry) == 800
        ann0 = rest.get_pod("default", "train-0").metadata.annotations
        assert ann0[consts.ANNOTATION_CONTAINER_PREFIX + "main"]

        with tempfile.TemporaryDirectory() as ckpt_dir:
            common = dict(
                model=TINY, batch_size=8, seq_len=16, lr=1e-2, seed=3,
            )
            # 2. "training" on the gang's 8 chips (2 nodes × 4): 3 steps,
            # checkpoint every step
            spec_a = JobSpec(
                mesh=MeshSpec(data=2, fsdp=2, tensor=2), steps=3,
                checkpoint_dir=ckpt_dir, checkpoint_every=1, **common,
            )
            losses_a = run_job(spec_a, pod_annotations=ann0,
                               container="main", devices=jax.devices()[:8])
            assert len(losses_a) == 3 and np.isfinite(losses_a).all()

            # the uninterrupted reference: same job, same data stream, 6
            # steps straight through on the original mesh
            ref = run_job(
                JobSpec(mesh=MeshSpec(data=2, fsdp=2, tensor=2), steps=6,
                        **common),
                devices=jax.devices()[:8],
            )
            assert np.allclose(ref[:3], losses_a, rtol=1e-4)

            # 3. node n1 dies mid-job: the node controller removes the
            # node and evicts its pod; the job controller tears down the
            # remaining member (gang semantics: all-or-nothing)
            api.delete_node("n1")
            api.delete_pod("default/train-1")
            api.delete_pod("default/train-0")
            # the watch stream delivers the deletes; the controller
            # releases ALL the gang's chips
            assert poll(lambda: used_core(registry) == 0, timeout=10)

            # 4. elastic replan: the job comes back at half size on the
            # surviving node — a single whole-node member
            solo = make_pod(
                "train-r0",
                containers=[
                    Container(
                        name="main",
                        resources=ResourceRequirements(
                            limits={consts.RESOURCE_TPU_CORE: 400}
                        ),
                    )
                ],
                uid="uid-train-r0",
            )
            doc = api.create_pod(solo)
            node = ks.schedule(doc, ["n0"])  # n1 is gone from the cluster
            assert node == "n0"
            res = ks.bind(doc, node)
            assert not res.get("Error"), res
            assert used_core(registry) == 400
            ann_r = rest.get_pod("default", "train-r0").metadata.annotations

            # 5. resume from checkpoint on the SMALLER mesh (4 chips):
            # trajectory continues exactly where the big mesh left off
            spec_b = JobSpec(
                mesh=MeshSpec(fsdp=2, tensor=2), steps=6,
                checkpoint_dir=ckpt_dir, checkpoint_every=1, **common,
            )
            losses_b = run_job(spec_b, pod_annotations=ann_r,
                               container="main", devices=jax.devices()[:4])
            assert len(losses_b) == 3  # resumed at step 3, ran 3..5
            assert np.allclose(losses_b, ref[3:], rtol=1e-4), (
                losses_b, ref[3:],
            )
    finally:
        server.stop()
        controller.stop()
