# Two-stage build (reference: Dockerfile:1-18 uses golang → debian-slim).
# Two runtime images from one file:
#   scheduler (default) — extender + controller + device plugin; no JAX.
#       docker build --target scheduler -t tpu-elastic-scheduler:latest .
#   workload — inference server / training launcher; adds the pinned JAX
#       stack so `python -m elastic_gpu_scheduler_tpu.serve` can import.
#       docker build --target workload -t tpu-elastic-inference:latest .
# Dependencies are pinned via requirements*.txt (the go.mod/go.sum
# analogue) so builds are reproducible.
# g++ is included so core/native.py can build the C++ placement extension
# at startup; numpy is a hard dependency of the topology core.
FROM python:3.12-slim AS base

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY requirements.txt requirements-workload.txt ./

FROM base AS scheduler
RUN pip install --no-cache-dir -r requirements.txt
COPY elastic_gpu_scheduler_tpu/ elastic_gpu_scheduler_tpu/
COPY native/ native/
COPY bench.py ./
EXPOSE 39999
ENTRYPOINT ["python", "-m", "elastic_gpu_scheduler_tpu.cli"]

FROM base AS workload
RUN pip install --no-cache-dir -r requirements-workload.txt
COPY elastic_gpu_scheduler_tpu/ elastic_gpu_scheduler_tpu/
COPY native/ native/
COPY bench.py ./
EXPOSE 8000
ENTRYPOINT ["python", "-m", "elastic_gpu_scheduler_tpu.serve"]
