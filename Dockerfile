# Two-stage build (reference: Dockerfile:1-18 uses golang → debian-slim; here
# the runtime is Python + grpc; protobuf messages are pre-generated in-tree).
FROM python:3.12-slim AS base

RUN pip install --no-cache-dir grpcio protobuf

WORKDIR /app
COPY elastic_gpu_scheduler_tpu/ elastic_gpu_scheduler_tpu/
COPY bench.py ./

EXPOSE 39999
ENTRYPOINT ["python", "-m", "elastic_gpu_scheduler_tpu.cli"]
