# Two-stage build (reference: Dockerfile:1-18 uses golang → debian-slim; here
# the runtime is Python + grpc; protobuf messages are pre-generated in-tree).
# g++ is included so core/native.py can build the C++ placement extension at
# startup; numpy is a hard dependency of the topology core.
FROM python:3.12-slim AS base

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/* \
    && pip install --no-cache-dir grpcio protobuf numpy

WORKDIR /app
COPY elastic_gpu_scheduler_tpu/ elastic_gpu_scheduler_tpu/
COPY native/ native/
COPY bench.py ./

EXPOSE 39999
ENTRYPOINT ["python", "-m", "elastic_gpu_scheduler_tpu.cli"]
